#!/usr/bin/env python
"""Capture a bounded device profile of a tiny live engine + print the
XLA cost registry — the offline face of the device-truth plane
(runtime/device_profiler.py).

Runs a tiny-model EngineCore for a few decode windows with the device
profiler enabled: the dispatch sites harvest XLA's cost analysis for
every compiled program (flops / bytes accessed), a bounded
jax.profiler capture runs over the steady windows, and the top-K
programs by bytes-accessed print as a table.  The capture directory is
`deviceprofile_<service>_<pid>` under --out-dir, mergeable onto host
trace lanes with `tools/trace_merge.py --device <dir>`.

Exits NONZERO when no xplane/trace output lands (a build without the
profiler plugin used to silently print an empty glob and exit 0 — a
no-op that read as success).

    JAX_PLATFORMS=cpu python tools/profile_trace.py --ms 300
    python tools/profile_trace.py --model llama-3-1b --out-dir /tmp/prof

For a LIVE worker use `/debug/deviceprofile?ms=N` on its status port or
the control-plane `profile/<pid>` command instead — this tool builds
its own throwaway engine.
"""

from __future__ import annotations

import argparse
import os
import sys
import threading

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        "tools/profile_trace.py", description=__doc__.splitlines()[0])
    p.add_argument("--model", default="tiny-test",
                   help="model config name (default tiny-test)")
    p.add_argument("--ms", type=int, default=500,
                   help="device-capture bound in milliseconds")
    p.add_argument("--out-dir", default="/tmp/dynamo_deviceprofile",
                   help="capture destination (the capture lands in a "
                        "deviceprofile_<service>_<pid> subdirectory)")
    p.add_argument("--steps", type=int, default=40,
                   help="engine steps to run under the capture")
    p.add_argument("--top", type=int, default=10,
                   help="programs to print from the cost registry")
    args = p.parse_args(argv)

    from dynamo_tpu.engine.engine import EngineConfig, EngineCore
    from dynamo_tpu.engine.sampling import SamplingParams
    from dynamo_tpu.engine.scheduler import SchedulerConfig
    from dynamo_tpu.models import config as mcfg
    from dynamo_tpu.runtime import device_profiler

    prof = device_profiler.configure(
        service="profile_trace", enabled=True,
        max_capture_ms=max(args.ms, 1), dump_dir=args.out_dir)

    core = EngineCore(EngineConfig(
        model=mcfg.get_config(args.model), num_blocks=128,
        enable_prefix_cache=False, decode_window=2,
        window_pipeline_depth=2,
        scheduler=SchedulerConfig(
            max_seqs=8, block_size=8, max_pages_per_seq=32,
            max_prefill_chunk=128, decode_buckets=(1, 2, 4, 8),
            prefill_buckets=(16, 128))))
    core.add_request("p0", list(range(1, 71)),
                     SamplingParams(max_tokens=max(args.steps, 8)))
    for _ in range(8):          # prefill + window warmup (compiles land)
        core.step()

    # The capture sleeps for its bound on a helper thread; stepping
    # stays HERE — the engine-thread contract pins step() to the thread
    # that warmed it up — so the device trace has real work under it.
    box = {}

    def run_capture():
        box["res"] = prof.capture(args.ms)

    t = threading.Thread(target=run_capture, daemon=True)
    t.start()
    while t.is_alive():
        core.step()
    t.join(timeout=10.0)
    res = box.get("res", {"ok": False, "error": "capture thread died"})

    print(f"registry: {prof.registry.size()} program(s) harvested "
          f"({prof.harvest_failures} failure(s))")
    rows = prof.registry.top_by("bytes_accessed", args.top)
    if rows:
        width = max(len(label) for label, _ in rows)
        print(f"{'program':<{width}}  {'bytes_accessed':>14}  "
              f"{'flops':>14}  optimal_s")
        for label, costs in rows:
            opt = costs.get("optimal_s")
            print(f"{label:<{width}}  {costs['bytes_accessed']:>14.0f}  "
                  f"{costs['flops']:>14.0f}  "
                  f"{opt if opt is not None else '-'}")

    if not res.get("ok"):
        print(f"error: device capture produced no trace output: "
              f"{res.get('error', 'unknown')}", file=sys.stderr)
        return 1
    print(f"capture: {res['ms']} ms -> {res['dir']}")
    for f in res["files"]:
        print(f"  {f}")
    print("merge onto host lanes with: "
          f"python tools/trace_merge.py <sources> --device {res['dir']}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
