#!/usr/bin/env python
"""Merge per-process /debug/traces buffers into one Perfetto file.

Every traced process (frontend, worker, router_service, planner) keeps a
bounded ring of completed traces and serves it at `/debug/traces?n=K`.
This tool pulls those buffers, stitches spans from all processes together
by trace_id, and writes Chrome trace-event JSON that Perfetto
(https://ui.perfetto.dev) or chrome://tracing loads directly — one
process lane per service, one thread lane per request, parented spans
intact across the frontend → router → RPC → worker → engine path.

    # two processes, most recent 64 traces each, open merged.json in Perfetto
    python tools/trace_merge.py http://127.0.0.1:8080 http://127.0.0.1:9201 \
        -o merged.json --n 64

    # offline: previously-saved /debug/traces payloads
    python tools/trace_merge.py frontend.json worker.json -o merged.json

Sources may be base URLs (the /debug/traces path is appended), full URLs,
or paths to saved payload files; spans duplicated across payloads (e.g.
co-located processes sharing a tracer) dedupe by (trace_id, span_id).

Flight-recorder dumps (ISSUE 14) merge into the same timeline:

    python tools/trace_merge.py http://127.0.0.1:8080 \
        --flight /tmp/flight_worker-backend_12345.jsonl -o merged.json

Each recorder event (admissions, dispatch shapes, recompiles, KV plane
choices, SLO transitions, stalls) becomes a Perfetto INSTANT marker on
the owning process's track, time-aligned with the trace spans by their
shared wall clock and deduped by (service, seq) — so "what was the
engine doing when this request went slow" is one view, not two files.

Request ledgers (ISSUE 18) merge the same way:

    python tools/trace_merge.py http://127.0.0.1:8080 \
        --ledger /tmp/requests.json -o merged.json

where requests.json is a saved `/debug/requests?n=K` payload (or a bare
list of ledger payloads).  Each phase stamp becomes a complete
("ph":"X") child span on the owning request's trace track — ledger
request ids ARE frontend trace ids, so the stamps land time-aligned
under the request's own spans; requests without a trace get a `ledger`
process lane.  Duplicate ledgers across dumps dedupe by request id.

On-demand device captures (ISSUE 20) merge the same way:

    python tools/trace_merge.py http://127.0.0.1:8080 \
        --device /tmp/deviceprofile_worker-backend_12345 -o merged.json

where the directory is what `/debug/deviceprofile?ms=N` (or the
control-plane `profile/<pid>` command) wrote: jax.profiler's Chrome
trace (`*.trace.json.gz`) plus the `capture_meta.json` sidecar
runtime/device_profiler.py drops next to it.  Device lanes (one per
XLA device/stream) land as their own process tracks named after the
owning worker service, with timestamps re-anchored from the sidecar's
wall clock — so host spans (ledger phases, flight markers) and the
device execution they paid for line up on one timeline.  Re-merging
the same capture dedupes by (service, lane, ts, name).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import urllib.error
import urllib.request
from typing import Dict, List

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from dynamo_tpu.runtime.tracing import chrome_trace  # noqa: E402


def fetch_payload(source: str, n: int, timeout: float = 5.0) -> dict:
    """One /debug/traces payload from a URL or a saved JSON file."""
    if source.startswith(("http://", "https://")):
        url = source
        if "/debug/traces" not in url:
            url = url.rstrip("/") + f"/debug/traces?n={n}"
        with urllib.request.urlopen(url, timeout=timeout) as resp:
            return json.loads(resp.read().decode("utf-8"))
    with open(source) as f:
        return json.load(f)


def merge_payloads(payloads: List[dict]) -> dict:
    """Merge /debug/traces payloads by trace_id → Chrome trace JSON.

    Only trace_ids seen in MORE than one payload — or in a single-source
    run, all of them — are interesting, but partial traces (a worker
    restarted, a ring overflowed) still render; missing parents just
    show as top-level slices in Perfetto."""
    by_trace: Dict[str, dict] = {}
    for payload in payloads:
        for trace in payload.get("traces", []):
            tid = trace.get("trace_id")
            if tid is None:
                continue
            merged = by_trace.setdefault(
                tid, {"trace_id": tid, "spans": [],
                      "services": set()})
            merged["spans"].extend(trace.get("spans", []))
            merged["services"].add(trace.get("service", "dynamo"))
            if trace.get("forced_slow_sample"):
                merged["forced_slow_sample"] = True
    traces = []
    for merged in by_trace.values():
        merged["services"] = sorted(merged["services"])
        merged["spans"].sort(key=lambda s: s.get("ts", 0.0))
        traces.append(merged)
    traces.sort(key=lambda t: t["spans"][0]["ts"] if t["spans"] else 0.0)
    return chrome_trace(traces)


def load_flight_dump(path: str) -> List[dict]:
    """Parse one flight-recorder JSONL dump into event dicts.  Header
    lines (`flight_dump: true`) set the owning service for the events
    that follow (a dump file may hold several appended dumps); malformed
    lines are skipped — a truncated crash dump must still merge."""
    events: List[dict] = []
    service = "flight"
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                row = json.loads(line)
            except ValueError:
                continue  # crash-truncated tail / faulthandler traceback
            if not isinstance(row, dict):
                continue
            if row.get("flight_dump"):
                service = row.get("service") or service
                continue
            if "ts" not in row or "kind" not in row:
                continue
            row["_service"] = service
            events.append(row)
    return events


def merge_flight_events(merged: dict, flight_events: List[dict]) -> int:
    """Append flight-recorder events to a Chrome trace doc as instant
    ("ph":"i") markers on the owning process's track, reusing the
    process lane the service's spans already occupy (or allocating a
    new one).  Dedupes by (service, seq) so overlapping dumps — e.g. a
    stall dump and the atexit dump of the same death — merge cleanly.
    Returns the number of events added."""
    events = merged["traceEvents"]
    pids: Dict[str, int] = {}
    max_pid = 0
    for ev in events:
        pid = ev.get("pid", 0)
        max_pid = max(max_pid, pid)
        if ev.get("ph") == "M" and ev.get("name") == "process_name":
            pids[ev["args"]["name"]] = pid
    seen: set = set()
    added = 0
    new_services: List[str] = []
    for row in flight_events:
        service = row.pop("_service", "flight")
        key = (service, row.get("seq"), row.get("ts"), row.get("kind"))
        if key in seen:
            continue
        seen.add(key)
        pid = pids.get(service)
        if pid is None:
            max_pid += 1
            pid = pids[service] = max_pid
            new_services.append(service)
        args = {k: v for k, v in row.items()
                if k not in ("ts", "kind")}
        events.append({
            "name": f"fr.{row['kind']}", "cat": "flight", "ph": "i",
            "s": "p",                      # process-scoped instant
            "ts": round(float(row["ts"]) * 1e6, 3),
            "pid": pid, "tid": 0, "args": args,
        })
        added += 1
    for service in new_services:
        events.append({"name": "process_name", "ph": "M",
                       "pid": pids[service], "tid": 0,
                       "args": {"name": service}})
    return added


def load_ledger_dump(path: str) -> List[dict]:
    """Parse one saved ledger dump into payload dicts.  Accepts the
    `/debug/requests` body (`{"slowest": [...]}`), a bare list of
    ledger payloads, or a single payload; entries without a request_id
    or stamps list are skipped — telemetry files must merge tolerantly
    or not at all, never raise."""
    with open(path) as f:
        doc = json.load(f)
    if isinstance(doc, dict):
        rows = doc.get("slowest") or doc.get("requests") \
            or ([doc] if doc.get("request_id") else [])
    elif isinstance(doc, list):
        rows = doc
    else:
        rows = []
    out = []
    for row in rows:
        if (isinstance(row, dict) and row.get("request_id")
                and isinstance(row.get("stamps"), list)):
            out.append(row)
    return out


def merge_ledger_spans(merged: dict, ledgers: List[dict]) -> int:
    """Append ledger phase stamps to a Chrome trace doc as complete
    ("ph":"X") spans on the owning request's track.  Frontend trace ids
    ARE request ids, so a request that was traced gets its ledger spans
    on the SAME (pid, tid) lane as its spans — time-aligned by the
    shared monotonic clock (stamp `t` is the phase END, so the span
    starts at `t - dur`).  Requests with no trace share one `ledger`
    process lane.  Dedupes by request id across dumps.  Returns the
    number of spans added."""
    events = merged["traceEvents"]
    lanes: Dict[str, tuple] = {}      # trace_id -> (pid, tid)
    max_pid = max_tid = 0
    for ev in events:
        max_pid = max(max_pid, ev.get("pid", 0))
        max_tid = max(max_tid, ev.get("tid", 0))
        tid_key = (ev.get("args") or {}).get("trace_id")
        if tid_key is not None and tid_key not in lanes:
            lanes[tid_key] = (ev["pid"], ev["tid"])
    ledger_pid = None
    seen: set = set()
    added = 0
    for led in ledgers:
        rid = led["request_id"]
        if rid in seen:
            continue
        seen.add(rid)
        lane = lanes.get(rid)
        if lane is None:
            if ledger_pid is None:
                max_pid += 1
                ledger_pid = max_pid
                events.append({"name": "process_name", "ph": "M",
                               "pid": ledger_pid, "tid": 0,
                               "args": {"name": "ledger"}})
            max_tid += 1
            lane = (ledger_pid, max_tid)
        pid, tid = lane
        for stamp in led["stamps"]:
            try:
                t, dur = float(stamp["t"]), float(stamp["dur"])
                phase = str(stamp["phase"])
            except (KeyError, TypeError, ValueError):
                continue  # partial dump: render what parses
            args = dict(stamp.get("attrs") or {})
            args["request_id"] = rid
            events.append({
                "name": f"ledger.{phase}", "cat": "ledger", "ph": "X",
                "ts": round((t - dur) * 1e6, 3),
                "dur": round(dur * 1e6, 3),
                "pid": pid, "tid": tid, "args": args,
            })
            added += 1
    return added


def load_device_capture(capture_dir: str) -> List[dict]:
    """Parse one device-capture directory (device_profiler.capture
    output) into per-trace-file dicts: {"service", "wall_start",
    "events": [...]}.  Service/pid come from the capture_meta.json
    sidecar when present, else from the deviceprofile_<service>_<pid>
    directory name; malformed or missing trace files are skipped — a
    partial capture must still merge."""
    import glob as globmod
    import gzip

    meta = {}
    meta_path = os.path.join(capture_dir, "capture_meta.json")
    if os.path.exists(meta_path):
        try:
            with open(meta_path) as f:
                meta = json.load(f)
        except (OSError, ValueError):
            meta = {}
    service = meta.get("service")
    if not service:
        base = os.path.basename(os.path.normpath(capture_dir))
        if base.startswith("deviceprofile_"):
            # deviceprofile_<service>_<pid> — the pid is the last part.
            service = base[len("deviceprofile_"):].rsplit("_", 1)[0]
        else:
            service = base or "device"
    out: List[dict] = []
    for path in sorted(globmod.glob(
            os.path.join(capture_dir, "**", "*.trace.json.gz"),
            recursive=True)):
        try:
            with gzip.open(path, "rt") as f:
                doc = json.load(f)
        except (OSError, ValueError):
            print(f"warning: skipping unreadable device trace {path}",
                  file=sys.stderr)
            continue
        events = (doc.get("traceEvents") if isinstance(doc, dict)
                  else doc)
        if not isinstance(events, list):
            continue
        out.append({"service": service,
                    "wall_start": meta.get("wall_start"),
                    "events": events})
    return out


def merge_device_events(merged: dict, captures: List[dict]) -> int:
    """Append device-capture trace events to a Chrome trace doc.  Each
    device lane (a pid in the capture's own numbering) becomes a fresh
    process track named `<service> device/<lane name>` so the capture
    sits visually next to the owning worker's host lanes.  The
    profiler's timestamps are relative to trace start — the sidecar's
    `wall_start` re-anchors them onto the shared wall clock the host
    spans use (captures without a sidecar merge un-anchored, still
    inspectable).  Dedupes by (service, lane, tid, ts, name, ph) so
    re-merging a capture adds nothing.  Returns events added."""
    events = merged["traceEvents"]
    max_pid = max((ev.get("pid", 0) for ev in events), default=0)
    seen: set = set()
    added = 0
    for cap in captures:
        service = cap["service"]
        offset_us = (float(cap["wall_start"]) * 1e6
                     if cap.get("wall_start") else 0.0)
        lane_names: Dict[int, str] = {}
        for ev in cap["events"]:
            if (ev.get("ph") == "M"
                    and ev.get("name") == "process_name"):
                name = (ev.get("args") or {}).get("name")
                if name is not None:
                    lane_names[ev.get("pid", 0)] = str(name)
        lane_pids: Dict[int, int] = {}
        for ev in cap["events"]:
            ph = ev.get("ph")
            if ph == "M":
                continue    # lane metadata re-emitted below, renamed
            if not ph:
                continue    # jax emits degenerate phase-less rows
                            # (nothing to render, nothing to anchor)
            try:
                ts = float(ev.get("ts", 0.0)) + offset_us
            except (TypeError, ValueError):
                continue
            lane = ev.get("pid", 0)
            key = (service, lane, ev.get("tid", 0), round(ts, 3),
                   ev.get("name"), ph)
            if key in seen:
                continue
            seen.add(key)
            pid = lane_pids.get(lane)
            if pid is None:
                max_pid += 1
                pid = lane_pids[lane] = max_pid
                lane_name = lane_names.get(lane, f"lane {lane}")
                events.append({
                    "name": "process_name", "ph": "M", "pid": pid,
                    "tid": 0,
                    "args": {"name": f"{service} device/{lane_name}"}})
            row = dict(ev)
            row["ts"] = round(ts, 3)
            row["pid"] = pid
            row.setdefault("cat", "device")
            events.append(row)
            added += 1
    return added


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        "tools/trace_merge.py", description=__doc__.splitlines()[0])
    p.add_argument("sources", nargs="+",
                   help="base URLs (http://host:port), full /debug/traces "
                        "URLs, or saved payload JSON files")
    p.add_argument("-o", "--out", default="merged_trace.json",
                   help="output Chrome trace JSON (default "
                        "merged_trace.json)")
    p.add_argument("--n", type=int, default=64,
                   help="traces to request per process (default 64)")
    p.add_argument("--flight", action="append", default=[],
                   metavar="DUMP.jsonl",
                   help="flight-recorder JSONL dump(s) "
                        "(runtime/flight_recorder.py) merged as instant "
                        "markers on the owning process track; repeatable")
    p.add_argument("--ledger", action="append", default=[],
                   metavar="DUMP.json",
                   help="saved /debug/requests payload(s) "
                        "(runtime/ledger.py) — each request's phase "
                        "stamps render as child spans on its own trace "
                        "track, deduped by request id; repeatable")
    p.add_argument("--device", action="append", default=[],
                   metavar="CAPTURE_DIR",
                   help="device-capture directory(ies) written by "
                        "/debug/deviceprofile?ms=N "
                        "(runtime/device_profiler.py) — jax.profiler's "
                        "device lanes merge as process tracks named "
                        "after the owning worker, re-anchored to the "
                        "wall clock via the capture_meta.json sidecar; "
                        "repeatable")
    args = p.parse_args(argv)

    payloads = []
    for src in args.sources:
        try:
            payloads.append(fetch_payload(src, args.n))
        except (urllib.error.URLError, OSError, ValueError) as e:
            print(f"warning: skipping {src}: {e}", file=sys.stderr)
    if not payloads:
        print("error: no source produced a payload", file=sys.stderr)
        return 1
    merged = merge_payloads(payloads)
    flight_events: List[dict] = []
    for fpath in args.flight:
        try:
            flight_events.extend(load_flight_dump(fpath))
        except OSError as e:
            print(f"warning: skipping flight dump {fpath}: {e}",
                  file=sys.stderr)
    n_flight = merge_flight_events(merged, flight_events) \
        if flight_events else 0
    ledgers: List[dict] = []
    for lpath in args.ledger:
        try:
            ledgers.extend(load_ledger_dump(lpath))
        except (OSError, ValueError) as e:
            print(f"warning: skipping ledger dump {lpath}: {e}",
                  file=sys.stderr)
    n_ledger = merge_ledger_spans(merged, ledgers) if ledgers else 0
    captures: List[dict] = []
    for dpath in args.device:
        try:
            captures.extend(load_device_capture(dpath))
        except OSError as e:
            print(f"warning: skipping device capture {dpath}: {e}",
                  file=sys.stderr)
    n_device = merge_device_events(merged, captures) if captures else 0
    n_spans = sum(1 for ev in merged["traceEvents"] if ev.get("ph") == "X")
    with open(args.out, "w") as f:
        json.dump(merged, f)
    extra = f" + {n_flight} flight event(s)" if n_flight else ""
    if n_ledger:
        extra += f" + {n_ledger} ledger span(s)"
    if n_device:
        extra += f" + {n_device} device event(s)"
    print(f"wrote {args.out}: {n_spans} spans from {len(payloads)} "
          f"process(es){extra} — open in https://ui.perfetto.dev")
    return 0


if __name__ == "__main__":
    sys.exit(main())
