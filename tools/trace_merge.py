#!/usr/bin/env python
"""Merge per-process /debug/traces buffers into one Perfetto file.

Every traced process (frontend, worker, router_service, planner) keeps a
bounded ring of completed traces and serves it at `/debug/traces?n=K`.
This tool pulls those buffers, stitches spans from all processes together
by trace_id, and writes Chrome trace-event JSON that Perfetto
(https://ui.perfetto.dev) or chrome://tracing loads directly — one
process lane per service, one thread lane per request, parented spans
intact across the frontend → router → RPC → worker → engine path.

    # two processes, most recent 64 traces each, open merged.json in Perfetto
    python tools/trace_merge.py http://127.0.0.1:8080 http://127.0.0.1:9201 \
        -o merged.json --n 64

    # offline: previously-saved /debug/traces payloads
    python tools/trace_merge.py frontend.json worker.json -o merged.json

Sources may be base URLs (the /debug/traces path is appended), full URLs,
or paths to saved payload files; spans duplicated across payloads (e.g.
co-located processes sharing a tracer) dedupe by (trace_id, span_id).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import urllib.error
import urllib.request
from typing import Dict, List

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from dynamo_tpu.runtime.tracing import chrome_trace  # noqa: E402


def fetch_payload(source: str, n: int, timeout: float = 5.0) -> dict:
    """One /debug/traces payload from a URL or a saved JSON file."""
    if source.startswith(("http://", "https://")):
        url = source
        if "/debug/traces" not in url:
            url = url.rstrip("/") + f"/debug/traces?n={n}"
        with urllib.request.urlopen(url, timeout=timeout) as resp:
            return json.loads(resp.read().decode("utf-8"))
    with open(source) as f:
        return json.load(f)


def merge_payloads(payloads: List[dict]) -> dict:
    """Merge /debug/traces payloads by trace_id → Chrome trace JSON.

    Only trace_ids seen in MORE than one payload — or in a single-source
    run, all of them — are interesting, but partial traces (a worker
    restarted, a ring overflowed) still render; missing parents just
    show as top-level slices in Perfetto."""
    by_trace: Dict[str, dict] = {}
    for payload in payloads:
        for trace in payload.get("traces", []):
            tid = trace.get("trace_id")
            if tid is None:
                continue
            merged = by_trace.setdefault(
                tid, {"trace_id": tid, "spans": [],
                      "services": set()})
            merged["spans"].extend(trace.get("spans", []))
            merged["services"].add(trace.get("service", "dynamo"))
            if trace.get("forced_slow_sample"):
                merged["forced_slow_sample"] = True
    traces = []
    for merged in by_trace.values():
        merged["services"] = sorted(merged["services"])
        merged["spans"].sort(key=lambda s: s.get("ts", 0.0))
        traces.append(merged)
    traces.sort(key=lambda t: t["spans"][0]["ts"] if t["spans"] else 0.0)
    return chrome_trace(traces)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        "tools/trace_merge.py", description=__doc__.splitlines()[0])
    p.add_argument("sources", nargs="+",
                   help="base URLs (http://host:port), full /debug/traces "
                        "URLs, or saved payload JSON files")
    p.add_argument("-o", "--out", default="merged_trace.json",
                   help="output Chrome trace JSON (default "
                        "merged_trace.json)")
    p.add_argument("--n", type=int, default=64,
                   help="traces to request per process (default 64)")
    args = p.parse_args(argv)

    payloads = []
    for src in args.sources:
        try:
            payloads.append(fetch_payload(src, args.n))
        except (urllib.error.URLError, OSError, ValueError) as e:
            print(f"warning: skipping {src}: {e}", file=sys.stderr)
    if not payloads:
        print("error: no source produced a payload", file=sys.stderr)
        return 1
    merged = merge_payloads(payloads)
    n_spans = sum(1 for ev in merged["traceEvents"] if ev["ph"] == "X")
    with open(args.out, "w") as f:
        json.dump(merged, f)
    print(f"wrote {args.out}: {n_spans} spans from {len(payloads)} "
          f"process(es) — open in https://ui.perfetto.dev")
    return 0


if __name__ == "__main__":
    sys.exit(main())
